"""Model registry — the ``keras_applications.py``† analog.

Maps model name -> Flax module constructor, Keras oracle constructor, input
geometry, preprocessing mode, and featurization cut-point size, mirroring the
reference's ``KERAS_APPLICATION_MODELS`` / ``getKerasApplicationModel`` and
its ``SUPPORTED_MODELS`` list (``python/sparkdl/transformers/named_image.py``†
consumed the same registry).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sparkdl_tpu.models.inception_v3 import InceptionV3
from sparkdl_tpu.models.mobilenet_v2 import MobileNetV2
from sparkdl_tpu.models.resnet import ResNet50
from sparkdl_tpu.models.vgg import VGG16, VGG19
from sparkdl_tpu.models.xception import Xception

_CAFFE_MEAN_BGR = (103.939, 116.779, 123.68)
_TORCH_MEAN = (0.485, 0.456, 0.406)
_TORCH_STD = (0.229, 0.224, 0.225)


def preprocess_input(x, mode: str):
    """Keras ``preprocess_input`` parity, jnp-traceable.

    ``x``: float RGB in [0, 255], NHWC.
    """
    if mode == "tf":
        return x / 127.5 - 1.0
    if mode == "caffe":
        x = x[..., ::-1]  # RGB -> BGR
        return x - jnp.asarray(_CAFFE_MEAN_BGR, dtype=x.dtype)
    if mode == "torch":
        x = x / 255.0
        return (x - jnp.asarray(_TORCH_MEAN, dtype=x.dtype)) / jnp.asarray(
            _TORCH_STD, dtype=x.dtype
        )
    raise ValueError(f"Unknown preprocessing mode: {mode!r}")


class KerasApplicationModel:
    """One registry entry: everything the transformers need to run a named
    pretrained CNN (the per-model class pattern of ``keras_applications.py``†).
    """

    def __init__(
        self,
        name: str,
        flax_cls,
        keras_name: str,
        input_size: Tuple[int, int],
        feature_size: int,
        preprocess_mode: str,
        num_classes: int = 1000,
        module_kwargs: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.flax_cls = flax_cls
        self.keras_name = keras_name
        self.input_size = input_size
        self.feature_size = feature_size
        self.preprocess_mode = preprocess_mode
        self.module_kwargs = dict(module_kwargs or {})
        self.num_classes = num_classes

    # -- geometry / preprocessing ------------------------------------
    def inputShape(self) -> Tuple[int, int]:
        return self.input_size

    def preprocess(self, x):
        return preprocess_input(x, self.preprocess_mode)

    # -- online serving hooks ----------------------------------------
    def serving_item_spec(self) -> Tuple[Tuple[int, int, int], Any]:
        """The per-item ``(shape, dtype)`` an online endpoint for this
        model serves — what ``ModelServer.register(item_shape=...)`` and
        a cold ``warmup()`` need before any request has arrived."""
        import numpy as np

        h, w = self.input_size
        return (h, w, 3), np.float32

    def warmup_buckets(self, max_batch: int = 32) -> Tuple[int, ...]:
        """The shape buckets an endpoint for this model should pre-trace
        (the full serving ladder; one program per bucket)."""
        from sparkdl_tpu.transformers.utils import bucket_ladder

        return bucket_ladder(max_batch)

    def serving_prologue(self):
        """The fused on-device input prologue for an online endpoint of
        this model: cast/bilinear-resize to the model's input size +
        Keras-parity :func:`preprocess_input`, as one jnp-traceable
        callable for ``ModelServer.register(prologue=...)`` — the
        decode-output → model-input pipeline compiles *into* the
        endpoint executable instead of round-tripping through the
        host-side ``device_resize`` shape groups."""
        from sparkdl_tpu.transformers.utils import make_input_prologue

        return make_input_prologue(
            size=self.input_size, preprocess=self.preprocess
        )

    # -- model construction ------------------------------------------
    def make_module(self, dtype: Optional[Any] = None, include_top: bool = True):
        return self.flax_cls(
            include_top=include_top, dtype=dtype, **self.module_kwargs
        )

    def keras_model(self, weights: Optional[str] = "imagenet"):
        """Build the Keras oracle/weight-source model (lazy keras import)."""
        import keras

        ctor = getattr(keras.applications, self.keras_name)
        return ctor(weights=weights, classifier_activation=None)

    def load_variables(self, weights="imagenet"):
        """Flax variables for this model.

        ``weights``: ``"imagenet"`` / ``None`` (delegated to Keras) or an
        already-built Keras model to port from.
        """
        from sparkdl_tpu.models.keras_port import port_keras_weights

        model = (
            weights
            if not isinstance(weights, (str, type(None)))
            else self.keras_model(weights)
        )
        variables = port_keras_weights(model)
        if self.module_kwargs:
            # TPU-layout module variants (e.g. Xception's lane-aligned
            # 768-wide middle flow) hold the Keras weights zero-padded;
            # numerics are unchanged (zero channels stay zero end to end)
            from sparkdl_tpu.models.keras_port import pad_variables_to_module

            variables = pad_variables_to_module(
                variables, self.make_module(), self.input_size
            )
        return variables

    def __repr__(self):
        return (
            f"KerasApplicationModel({self.name}, input={self.input_size}, "
            f"features={self.feature_size}, mode={self.preprocess_mode!r})"
        )


KERAS_APPLICATION_MODELS: Dict[str, KerasApplicationModel] = {
    m.name: m
    for m in [
        KerasApplicationModel("InceptionV3", InceptionV3, "InceptionV3",
                              (299, 299), 2048, "tf"),
        # middle_width=768 (vs Keras's 728): 6x128 MXU lane alignment
        # buys +20% throughput on this chip for +5.6% padded FLOPs
        # (BASELINE.md r4 receipts); Keras weights port zero-padded,
        # numerics unchanged
        KerasApplicationModel("Xception", Xception, "Xception",
                              (299, 299), 2048, "tf",
                              module_kwargs={"middle_width": 768}),
        KerasApplicationModel("ResNet50", ResNet50, "ResNet50",
                              (224, 224), 2048, "caffe"),
        KerasApplicationModel("VGG16", VGG16, "VGG16",
                              (224, 224), 4096, "caffe"),
        KerasApplicationModel("VGG19", VGG19, "VGG19",
                              (224, 224), 4096, "caffe"),
        KerasApplicationModel("MobileNetV2", MobileNetV2, "MobileNetV2",
                              (224, 224), 1280, "tf"),
    ]
}

# The reference's SUPPORTED_MODELS (named_image.py†) plus MobileNetV2.
SUPPORTED_MODELS = tuple(KERAS_APPLICATION_MODELS)


def get_keras_application_model(name: str) -> KerasApplicationModel:
    if name not in KERAS_APPLICATION_MODELS:
        raise ValueError(
            f"Unsupported model: {name!r}. Supported: {sorted(SUPPORTED_MODELS)}"
        )
    return KERAS_APPLICATION_MODELS[name]


# Reference-spelling alias (sparkdl.transformers.keras_applications†).
getKerasApplicationModel = get_keras_application_model


def fold_bgr_flip_into_stem(variables, preprocess_mode: str):
    """Fold the BGR->RGB input flip into the stem conv's weights.

    The transformers' fused forward flips the stored-BGR batch before the
    CNN (``x[..., ::-1]``) — a pure-bandwidth op XLA cannot elide.  When
    the model's preprocessing is channel-symmetric (``"tf"`` mode: the same
    affine per channel), reversing the *input-channel axis of the first
    conv kernel* is mathematically identical, and the flip disappears from
    the program entirely.

    Pass the entry's ``preprocess_mode``: folding under channel-asymmetric
    preprocessing (``"caffe"`` per-channel mean subtraction) would change
    the numerics, so any mode other than ``"tf"`` returns ``None`` here —
    the gate lives in this helper precisely so call sites cannot forget it
    (benchmarks/profile_ops.py once did, and profiled a numerically wrong
    program for VGG/ResNet).

    Returns the folded variables, or ``None`` when folding is unsafe
    (non-'tf' preprocessing, or not exactly one 3-input-channel conv
    kernel — caller keeps the runtime flip).
    """
    if preprocess_mode != "tf":
        return None
    flat, treedef = jax.tree_util.tree_flatten_with_path(variables)
    hits = [
        i
        for i, (path, leaf) in enumerate(flat)
        if getattr(leaf, "ndim", 0) == 4
        and leaf.shape[2] == 3
        and any(getattr(k, "key", None) == "kernel" for k in path)
    ]
    if len(hits) != 1:
        return None
    leaves = [leaf for _, leaf in flat]
    i = hits[0]
    leaves[i] = leaves[i][:, :, ::-1, :]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def decode_predictions(preds, top: int = 5):
    """``imagenet_utils.decode_predictions`` analog.

    Label priority: Keras's cached ``imagenet_class_index.json`` (real
    wnids + names) when present, else the vendored class-name list
    (:mod:`sparkdl_tpu.models.imagenet_labels` — real names, synthetic
    wnid placeholders; no network needed).  Accepts logits or
    probabilities, shape (batch, 1000).
    """
    import numpy as np

    preds = np.asarray(preds)
    class_index = None
    try:  # pragma: no cover - depends on local keras cache
        import json
        import os

        path = os.path.expanduser(
            "~/.keras/models/imagenet_class_index.json"
        )
        if os.path.exists(path):
            with open(path) as fh:
                class_index = json.load(fh)
    except Exception:
        class_index = None

    from sparkdl_tpu.models.imagenet_labels import IMAGENET_CLASS_NAMES

    results = []
    for row in preds:
        top_idx = row.argsort()[-top:][::-1]
        entries = []
        is_imagenet_shaped = row.shape[-1] == 1000
        for i in top_idx:
            i = int(i)
            if class_index is not None and is_imagenet_shaped:
                wnid, label = class_index[str(i)]
            elif is_imagenet_shaped and i < len(IMAGENET_CLASS_NAMES):
                wnid, label = f"n{i:08d}", IMAGENET_CLASS_NAMES[i]
            else:
                wnid, label = f"n{i:08d}", f"class_{i}"
            entries.append((wnid, label, float(row[i])))
        results.append(entries)
    return results
