"""Keras -> Flax weight porting.

The reference reused ``keras.applications`` weights directly (its models *were*
Keras models, frozen to GraphDefs — ``keras_applications.py``†,
``keras_utils.py``†).  Here pretrained/user Keras weights are ported into the
Flax model zoo's parameter pytrees.

Mapping strategy: Keras auto-generated layer names (``conv2d_37``,
``batch_normalization_5``...) shift by a global uid offset between
constructions, but their per-type *ordering* in ``model.layers`` is stable.
``normalized_layer_names`` renumbers each auto-named type from zero in layer
order, which yields deterministic names the Flax modules hardcode.  Explicitly
named layers (``conv1_conv``, ``block1_sepconv1``...) pass through unchanged.
"""

from __future__ import annotations

import re
from typing import Any, Dict

import jax.numpy as jnp

# Keras auto-name prefixes that get renumbered per type.
_AUTO_PREFIXES = frozenset(
    {
        "conv2d",
        "batch_normalization",
        "dense",
        "depthwise_conv2d",
        "separable_conv2d",
        "activation",
        "concatenate",
        "max_pooling2d",
        "average_pooling2d",
        "global_average_pooling2d",
        "dropout",
        "input_layer",
        "zero_padding2d",
        "add",
        "flatten",
        "rescaling",
    }
)

_SUFFIX_RE = re.compile(r"^(.*?)(?:_(\d+))?$")


def normalized_layer_names(model) -> Dict[str, str]:
    """Map each Keras layer's session-dependent name to a deterministic one.

    Keras uid suffixes increment in layer *creation* order (which matches the
    application code order the Flax modules mirror), while ``model.layers`` is
    topologically sorted — so normalization subtracts the per-prefix minimum
    suffix rather than renumbering by list position.
    """
    minima: Dict[str, int] = {}
    parsed: Dict[str, tuple] = {}
    for layer in model.layers:
        m = _SUFFIX_RE.match(layer.name)
        base, suffix = m.group(1), int(m.group(2) or 0)
        parsed[layer.name] = (base, suffix)
        if base in _AUTO_PREFIXES:
            minima[base] = min(minima.get(base, suffix), suffix)
    out: Dict[str, str] = {}
    for layer in model.layers:
        base, suffix = parsed[layer.name]
        if base in _AUTO_PREFIXES:
            idx = suffix - minima[base]
            out[layer.name] = base if idx == 0 else f"{base}_{idx}"
        else:
            out[layer.name] = layer.name
    return out


def port_keras_weights(model) -> Dict[str, Any]:
    """Convert a built Keras model's weights to Flax variable collections.

    Returns ``{"params": {...}, "batch_stats": {...}}`` keyed by normalized
    layer name, with per-layer leaves following Flax conventions
    (``kernel``/``bias`` for convs and dense, ``scale``/``bias`` +
    ``mean``/``var`` for batch norm, ``depthwise_kernel``/``pointwise_kernel``
    for separable convs).
    """
    names = normalized_layer_names(model)
    params: Dict[str, Any] = {}
    batch_stats: Dict[str, Any] = {}
    for layer in model.layers:
        weights = layer.get_weights()
        if not weights:
            continue
        name = names[layer.name]
        cls = type(layer).__name__
        if cls == "Conv2D":
            entry = {"kernel": jnp.asarray(weights[0])}
            if getattr(layer, "use_bias", False):
                entry["bias"] = jnp.asarray(weights[1])
            params[name] = entry
        elif cls == "DepthwiseConv2D":
            # Keras (kh, kw, cin, mult=1) -> flax grouped-conv HWIO (kh, kw, 1, cin)
            kernel = weights[0]
            entry = {"kernel": jnp.asarray(kernel.transpose(0, 1, 3, 2))}
            if getattr(layer, "use_bias", False):
                entry["bias"] = jnp.asarray(weights[1])
            params[name] = entry
        elif cls == "SeparableConv2D":
            entry = {
                "depthwise_kernel": jnp.asarray(weights[0].transpose(0, 1, 3, 2)),
                "pointwise_kernel": jnp.asarray(weights[1]),
            }
            if getattr(layer, "use_bias", False):
                entry["bias"] = jnp.asarray(weights[2])
            params[name] = entry
        elif cls == "Dense":
            entry = {"kernel": jnp.asarray(weights[0])}
            if getattr(layer, "use_bias", False):
                entry["bias"] = jnp.asarray(weights[1])
            params[name] = entry
        elif cls == "BatchNormalization":
            idx = 0
            entry = {}
            if layer.scale:
                entry["scale"] = jnp.asarray(weights[idx])
                idx += 1
            if layer.center:
                entry["bias"] = jnp.asarray(weights[idx])
                idx += 1
            batch_stats[name] = {
                "mean": jnp.asarray(weights[idx]),
                "var": jnp.asarray(weights[idx + 1]),
            }
            if entry:
                params[name] = entry
        else:
            raise NotImplementedError(
                f"No porting rule for Keras layer {layer.name} of type {cls}"
            )
    return {"params": params, "batch_stats": batch_stats}


def pad_variables_to_module(variables, module, input_size):
    """Zero-pad ported Keras weights up to a widened TPU-layout module.

    Some registry modules widen channel trunks for MXU lane alignment
    (e.g. Xception's 728 -> 768 = 6x128 middle flow, +20% measured
    throughput — BASELINE.md r4).  The target shapes come from
    ``jax.eval_shape(module.init)``; every leaf whose target is wider
    pads at the high end of the differing axes with zeros — except BN
    running variances, which pad with ones (identity statistics).  The
    padded channels then stay exactly zero through depthwise convs
    (zero kernels), pointwise convs (zero rows/columns), BN (zero
    scale/bias on zero-mean unit-var stats) and relu, so the widened
    model computes bit-for-bit what the Keras weights define on the
    original channels.
    """
    import jax

    h, w = input_size
    target = jax.eval_shape(
        module.init,
        jax.random.PRNGKey(0),
        jnp.zeros((1, h, w, 3), jnp.float32),
    )
    # lookup by path rather than strict structure matching: ported
    # variables may be a SUBSET of the module tree (a topless Keras
    # model has no 'predictions' layer, which featurization never uses)
    target_shapes = {
        jax.tree_util.keystr(p): tuple(l.shape)
        for p, l in jax.tree_util.tree_leaves_with_path(target)
    }

    def pad(path, leaf):
        key = jax.tree_util.keystr(path)
        if key not in target_shapes:
            raise ValueError(
                f"ported weight {key} has no counterpart in the module"
            )
        tshape = target_shapes[key]
        if tuple(leaf.shape) == tshape:
            return leaf
        if leaf.ndim != len(tshape):
            raise ValueError(
                f"rank mismatch at {key}: {leaf.shape} vs {tshape}"
            )
        pads = []
        for have, want in zip(leaf.shape, tshape):
            if want < have:
                raise ValueError(
                    f"target narrower than ported weights at "
                    f"{key}: {leaf.shape} vs {tshape}"
                )
            pads.append((0, want - have))
        is_var = getattr(path[-1], "key", None) == "var"
        return jnp.pad(
            jnp.asarray(leaf), pads,
            constant_values=1.0 if is_var else 0.0,
        )

    return jax.tree_util.tree_map_with_path(pad, variables)
