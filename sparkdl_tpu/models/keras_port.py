"""Keras -> Flax weight porting.

The reference reused ``keras.applications`` weights directly (its models *were*
Keras models, frozen to GraphDefs — ``keras_applications.py``†,
``keras_utils.py``†).  Here pretrained/user Keras weights are ported into the
Flax model zoo's parameter pytrees.

Mapping strategy: Keras auto-generated layer names (``conv2d_37``,
``batch_normalization_5``...) shift by a global uid offset between
constructions, but their per-type *ordering* in ``model.layers`` is stable.
``normalized_layer_names`` renumbers each auto-named type from zero in layer
order, which yields deterministic names the Flax modules hardcode.  Explicitly
named layers (``conv1_conv``, ``block1_sepconv1``...) pass through unchanged.
"""

from __future__ import annotations

import re
from typing import Any, Dict

import jax.numpy as jnp

# Keras auto-name prefixes that get renumbered per type.
_AUTO_PREFIXES = frozenset(
    {
        "conv2d",
        "batch_normalization",
        "dense",
        "depthwise_conv2d",
        "separable_conv2d",
        "activation",
        "concatenate",
        "max_pooling2d",
        "average_pooling2d",
        "global_average_pooling2d",
        "dropout",
        "input_layer",
        "zero_padding2d",
        "add",
        "flatten",
        "rescaling",
    }
)

_SUFFIX_RE = re.compile(r"^(.*?)(?:_(\d+))?$")


def normalized_layer_names(model) -> Dict[str, str]:
    """Map each Keras layer's session-dependent name to a deterministic one.

    Keras uid suffixes increment in layer *creation* order (which matches the
    application code order the Flax modules mirror), while ``model.layers`` is
    topologically sorted — so normalization subtracts the per-prefix minimum
    suffix rather than renumbering by list position.
    """
    minima: Dict[str, int] = {}
    parsed: Dict[str, tuple] = {}
    for layer in model.layers:
        m = _SUFFIX_RE.match(layer.name)
        base, suffix = m.group(1), int(m.group(2) or 0)
        parsed[layer.name] = (base, suffix)
        if base in _AUTO_PREFIXES:
            minima[base] = min(minima.get(base, suffix), suffix)
    out: Dict[str, str] = {}
    for layer in model.layers:
        base, suffix = parsed[layer.name]
        if base in _AUTO_PREFIXES:
            idx = suffix - minima[base]
            out[layer.name] = base if idx == 0 else f"{base}_{idx}"
        else:
            out[layer.name] = layer.name
    return out


def port_keras_weights(model) -> Dict[str, Any]:
    """Convert a built Keras model's weights to Flax variable collections.

    Returns ``{"params": {...}, "batch_stats": {...}}`` keyed by normalized
    layer name, with per-layer leaves following Flax conventions
    (``kernel``/``bias`` for convs and dense, ``scale``/``bias`` +
    ``mean``/``var`` for batch norm, ``depthwise_kernel``/``pointwise_kernel``
    for separable convs).
    """
    names = normalized_layer_names(model)
    params: Dict[str, Any] = {}
    batch_stats: Dict[str, Any] = {}
    for layer in model.layers:
        weights = layer.get_weights()
        if not weights:
            continue
        name = names[layer.name]
        cls = type(layer).__name__
        if cls == "Conv2D":
            entry = {"kernel": jnp.asarray(weights[0])}
            if getattr(layer, "use_bias", False):
                entry["bias"] = jnp.asarray(weights[1])
            params[name] = entry
        elif cls == "DepthwiseConv2D":
            # Keras (kh, kw, cin, mult=1) -> flax grouped-conv HWIO (kh, kw, 1, cin)
            kernel = weights[0]
            entry = {"kernel": jnp.asarray(kernel.transpose(0, 1, 3, 2))}
            if getattr(layer, "use_bias", False):
                entry["bias"] = jnp.asarray(weights[1])
            params[name] = entry
        elif cls == "SeparableConv2D":
            entry = {
                "depthwise_kernel": jnp.asarray(weights[0].transpose(0, 1, 3, 2)),
                "pointwise_kernel": jnp.asarray(weights[1]),
            }
            if getattr(layer, "use_bias", False):
                entry["bias"] = jnp.asarray(weights[2])
            params[name] = entry
        elif cls == "Dense":
            entry = {"kernel": jnp.asarray(weights[0])}
            if getattr(layer, "use_bias", False):
                entry["bias"] = jnp.asarray(weights[1])
            params[name] = entry
        elif cls == "BatchNormalization":
            idx = 0
            entry = {}
            if layer.scale:
                entry["scale"] = jnp.asarray(weights[idx])
                idx += 1
            if layer.center:
                entry["bias"] = jnp.asarray(weights[idx])
                idx += 1
            batch_stats[name] = {
                "mean": jnp.asarray(weights[idx]),
                "var": jnp.asarray(weights[idx + 1]),
            }
            if entry:
                params[name] = entry
        else:
            raise NotImplementedError(
                f"No porting rule for Keras layer {layer.name} of type {cls}"
            )
    return {"params": params, "batch_stats": batch_stats}
