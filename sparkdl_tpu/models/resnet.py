"""ResNet50 (v1, post-activation) in Flax.

Parity target: ``keras.applications.resnet.ResNet50`` — explicit stable layer
names (``conv1_conv``, ``conv{S}_block{B}_{i}_conv`` / ``_bn``), convs with
bias, BN epsilon 1.001e-5, stride carried by the first 1x1 conv of each
block (Keras v1 convention).  Featurization cut point: global-average-pool
output (``avg_pool``), 2048 features.  Input 224x224x3, "caffe"
preprocessing.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from sparkdl_tpu.models.layers import global_avg_pool, max_pool

_BN_EPS = 1.001e-5


class ResNet50(nn.Module):
    num_classes: int = 1000
    include_top: bool = True
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False, features_only: bool = False):
        def conv(y, filters, kernel, name, strides=1, padding="VALID"):
            return nn.Conv(
                filters,
                (kernel, kernel),
                strides=(strides, strides),
                padding=padding,
                use_bias=True,
                dtype=self.dtype,
                name=name,
            )(y)

        def bn(y, name):
            return nn.BatchNorm(
                use_running_average=not train,
                epsilon=_BN_EPS,
                dtype=self.dtype,
                name=name,
            )(y)

        def block(y, filters, name, stride=1, conv_shortcut=True):
            if conv_shortcut:
                shortcut = conv(y, 4 * filters, 1, f"{name}_0_conv", strides=stride)
                shortcut = bn(shortcut, f"{name}_0_bn")
            else:
                shortcut = y
            y = nn.relu(bn(conv(y, filters, 1, f"{name}_1_conv", strides=stride),
                           f"{name}_1_bn"))
            y = nn.relu(bn(conv(y, filters, 3, f"{name}_2_conv", padding="SAME"),
                           f"{name}_2_bn"))
            y = bn(conv(y, 4 * filters, 1, f"{name}_3_conv"), f"{name}_3_bn")
            return nn.relu(shortcut + y)

        def stack(y, filters, n_blocks, name, stride1=2):
            y = block(y, filters, f"{name}_block1", stride=stride1)
            for i in range(2, n_blocks + 1):
                y = block(y, filters, f"{name}_block{i}", conv_shortcut=False)
            return y

        x = jnp.pad(x, ((0, 0), (3, 3), (3, 3), (0, 0)))
        x = conv(x, 64, 7, "conv1_conv", strides=2)
        x = nn.relu(bn(x, "conv1_bn"))
        x = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
        x = max_pool(x, 3, 2)
        x = stack(x, 64, 3, "conv2", stride1=1)
        x = stack(x, 128, 4, "conv3")
        x = stack(x, 256, 6, "conv4")
        x = stack(x, 512, 3, "conv5")
        x = global_avg_pool(x)
        if features_only or not self.include_top:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype, name="predictions")(x)
