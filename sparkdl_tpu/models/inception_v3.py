"""InceptionV3 in Flax (NHWC, TPU-native).

Architecture parity target: ``keras.applications.inception_v3`` — the model
the reference's flagship ``DeepImageFeaturizer(modelName="InceptionV3")``
wraps (``python/sparkdl/transformers/keras_applications.py``†).  Layer names
are the normalized Keras auto-names (``conv2d``, ``conv2d_1``, ...,
``batch_normalization_N``) in Keras code-creation order so
``keras_port.port_keras_weights`` output drops straight in.

Cut point for featurization (``DeepImageFeaturizer``): global-average-pool
output, 2048 features.  Default input 299x299x3, "tf" preprocessing
(x/127.5 - 1).
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from sparkdl_tpu.models.layers import avg_pool, global_avg_pool, max_pool


class InceptionV3(nn.Module):
    num_classes: int = 1000
    include_top: bool = True
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False, features_only: bool = False):
        counter = [0]

        def conv_bn(y, filters, kh, kw, strides=(1, 1), padding="SAME"):
            i = counter[0]
            counter[0] += 1
            conv_name = "conv2d" if i == 0 else f"conv2d_{i}"
            bn_name = (
                "batch_normalization" if i == 0 else f"batch_normalization_{i}"
            )
            y = nn.Conv(
                filters,
                (kh, kw),
                strides=strides,
                padding=padding,
                use_bias=False,
                dtype=self.dtype,
                name=conv_name,
            )(y)
            y = nn.BatchNorm(
                use_running_average=not train,
                use_scale=False,
                epsilon=1e-3,
                dtype=self.dtype,
                name=bn_name,
            )(y)
            return nn.relu(y)

        # ---- stem ----
        x = conv_bn(x, 32, 3, 3, strides=(2, 2), padding="VALID")
        x = conv_bn(x, 32, 3, 3, padding="VALID")
        x = conv_bn(x, 64, 3, 3)
        x = max_pool(x, 3, 2)
        x = conv_bn(x, 80, 1, 1, padding="VALID")
        x = conv_bn(x, 192, 3, 3, padding="VALID")
        x = max_pool(x, 3, 2)

        # ---- mixed0..mixed2 (35x35) ----
        for pool_features in (32, 64, 64):
            b1 = conv_bn(x, 64, 1, 1)
            b5 = conv_bn(x, 48, 1, 1)
            b5 = conv_bn(b5, 64, 5, 5)
            b3d = conv_bn(x, 64, 1, 1)
            b3d = conv_bn(b3d, 96, 3, 3)
            b3d = conv_bn(b3d, 96, 3, 3)
            bp = avg_pool(x, 3, 1, "SAME")
            bp = conv_bn(bp, pool_features, 1, 1)
            x = jnp.concatenate([b1, b5, b3d, bp], axis=-1)

        # ---- mixed3 (reduce to 17x17) ----
        b3 = conv_bn(x, 384, 3, 3, strides=(2, 2), padding="VALID")
        b3d = conv_bn(x, 64, 1, 1)
        b3d = conv_bn(b3d, 96, 3, 3)
        b3d = conv_bn(b3d, 96, 3, 3, strides=(2, 2), padding="VALID")
        bp = max_pool(x, 3, 2)
        x = jnp.concatenate([b3, b3d, bp], axis=-1)

        # ---- mixed4..mixed7 (17x17, factorized 7x7) ----
        for c in (128, 160, 160, 192):
            b1 = conv_bn(x, 192, 1, 1)
            b7 = conv_bn(x, c, 1, 1)
            b7 = conv_bn(b7, c, 1, 7)
            b7 = conv_bn(b7, 192, 7, 1)
            b7d = conv_bn(x, c, 1, 1)
            b7d = conv_bn(b7d, c, 7, 1)
            b7d = conv_bn(b7d, c, 1, 7)
            b7d = conv_bn(b7d, c, 7, 1)
            b7d = conv_bn(b7d, 192, 1, 7)
            bp = avg_pool(x, 3, 1, "SAME")
            bp = conv_bn(bp, 192, 1, 1)
            x = jnp.concatenate([b1, b7, b7d, bp], axis=-1)

        # ---- mixed8 (reduce to 8x8) ----
        b3 = conv_bn(x, 192, 1, 1)
        b3 = conv_bn(b3, 320, 3, 3, strides=(2, 2), padding="VALID")
        b7x3 = conv_bn(x, 192, 1, 1)
        b7x3 = conv_bn(b7x3, 192, 1, 7)
        b7x3 = conv_bn(b7x3, 192, 7, 1)
        b7x3 = conv_bn(b7x3, 192, 3, 3, strides=(2, 2), padding="VALID")
        bp = max_pool(x, 3, 2)
        x = jnp.concatenate([b3, b7x3, bp], axis=-1)

        # ---- mixed9, mixed10 (8x8, expanded filter banks) ----
        for _ in range(2):
            b1 = conv_bn(x, 320, 1, 1)
            b3 = conv_bn(x, 384, 1, 1)
            b3_1 = conv_bn(b3, 384, 1, 3)
            b3_2 = conv_bn(b3, 384, 3, 1)
            b3 = jnp.concatenate([b3_1, b3_2], axis=-1)
            b3d = conv_bn(x, 448, 1, 1)
            b3d = conv_bn(b3d, 384, 3, 3)
            b3d_1 = conv_bn(b3d, 384, 1, 3)
            b3d_2 = conv_bn(b3d, 384, 3, 1)
            b3d = jnp.concatenate([b3d_1, b3d_2], axis=-1)
            bp = avg_pool(x, 3, 1, "SAME")
            bp = conv_bn(bp, 192, 1, 1)
            x = jnp.concatenate([b1, b3, b3d, bp], axis=-1)

        x = global_avg_pool(x)
        if features_only or not self.include_top:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype, name="predictions")(x)
