"""Pretrained-weight ingestion for the ViT family (VERDICT r2 missing #2).

The CNN zoo ingests ``keras.applications`` weights (`models/keras_port.py`,
the ``keras_applications.py``† "weights='imagenet'" contract analog).  ViT
has no keras.applications source, so this module ingests the two real-world
ViT artifact families instead:

- **google-research/vision_transformer ``.npz``** — the checkpoint format
  the original ViT repo publishes (``ViT-B_16.npz`` etc.):
  ``Transformer/encoderblock_{i}/MultiHeadDotProductAttention_1/query/kernel``
  naming with per-head-factored attention weights.  :func:`export_vit_npz`
  writes the same naming, so offline environments can round-trip
  self-produced artifacts through the identical ingestion path a user would
  feed a downloaded checkpoint through.
- **HuggingFace ``transformers`` torch ViT** (``ViTModel`` /
  ``ViTForImageClassification``) — an independent implementation, which
  also makes it the numerics oracle: ported logits must equal the torch
  forward (``tests/test_vit_port.py``; HF uses exact erf-gelu, so apply
  the result with ``ViT(exact_gelu=True)``).

Both return the ``{"params": ...}`` variables pytree of
:class:`sparkdl_tpu.models.vit.ViT`, ready for ``module.apply`` or
``FlaxImageFileEstimator(initialVariables=...)``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp


def _infer_geometry(params: Dict[str, Any]):
    """(dim, depth) from a ported tree — used for validation messages."""
    dim = params["patch_embed"]["kernel"].shape[-1]
    depth = sum(1 for k in params if k.startswith("block_"))
    return dim, depth


# ---------------------------------------------------------------------------
# HuggingFace transformers (torch) ViT
# ---------------------------------------------------------------------------

def port_hf_vit(hf_model) -> Dict[str, Any]:
    """Port a ``transformers`` ViT (``ViTModel`` or
    ``ViTForImageClassification``) to the :class:`ViT` variables pytree.

    The fused ``qkv`` kernel is the concatenation of HF's separate
    query/key/value projections (our block splits thirds back out); torch
    ``Linear`` weights are ``(out, in)`` so every dense kernel transposes.
    Apply with ``ViT(exact_gelu=True)`` — HF's "gelu" is the exact erf
    form, not flax's default tanh approximation.
    """
    sd = {k: np.asarray(v.detach().cpu().numpy())
          for k, v in hf_model.state_dict().items()}
    prefix = "vit." if any(k.startswith("vit.") for k in sd) else ""

    def g(name):
        return sd[prefix + name]

    params: Dict[str, Any] = {}
    # torch conv OIHW -> flax HWIO
    params["patch_embed"] = {
        "kernel": jnp.asarray(
            g("embeddings.patch_embeddings.projection.weight"
              ).transpose(2, 3, 1, 0)
        ),
        "bias": jnp.asarray(g("embeddings.patch_embeddings.projection.bias")),
    }
    params["cls_token"] = jnp.asarray(g("embeddings.cls_token"))
    params["pos_embed"] = jnp.asarray(g("embeddings.position_embeddings"))

    import re

    layer_ids = [
        int(m.group(1))
        for k in sd
        if (m := re.search(r"encoder\.layer\.(\d+)\.", k))
    ]
    depth = 1 + max(layer_ids)
    for i in range(depth):
        p = f"encoder.layer.{i}."
        wq = g(p + "attention.attention.query.weight").T
        wk = g(p + "attention.attention.key.weight").T
        wv = g(p + "attention.attention.value.weight").T
        bq = g(p + "attention.attention.query.bias")
        bk = g(p + "attention.attention.key.bias")
        bv = g(p + "attention.attention.value.bias")
        params[f"block_{i}"] = {
            "ln_1": {
                "scale": jnp.asarray(g(p + "layernorm_before.weight")),
                "bias": jnp.asarray(g(p + "layernorm_before.bias")),
            },
            "qkv": {
                "kernel": jnp.asarray(np.concatenate([wq, wk, wv], axis=1)),
                "bias": jnp.asarray(np.concatenate([bq, bk, bv])),
            },
            "proj": {
                "kernel": jnp.asarray(g(p + "attention.output.dense.weight").T),
                "bias": jnp.asarray(g(p + "attention.output.dense.bias")),
            },
            "ln_2": {
                "scale": jnp.asarray(g(p + "layernorm_after.weight")),
                "bias": jnp.asarray(g(p + "layernorm_after.bias")),
            },
            "mlp_up": {
                "kernel": jnp.asarray(g(p + "intermediate.dense.weight").T),
                "bias": jnp.asarray(g(p + "intermediate.dense.bias")),
            },
            "mlp_down": {
                "kernel": jnp.asarray(g(p + "output.dense.weight").T),
                "bias": jnp.asarray(g(p + "output.dense.bias")),
            },
        }
    params["ln_final"] = {
        "scale": jnp.asarray(g("layernorm.weight")),
        "bias": jnp.asarray(g("layernorm.bias")),
    }
    if "classifier.weight" in sd:  # ViTForImageClassification head
        params["head"] = {
            "kernel": jnp.asarray(sd["classifier.weight"].T),
            "bias": jnp.asarray(sd["classifier.bias"]),
        }
    return {"params": params}


def adapt_vit_variables(
    variables: Dict[str, Any],
    image_size: int,
    num_classes: Optional[int] = None,
) -> Dict[str, Any]:
    """Adapt ported ViT variables to a different fine-tune geometry — the
    two standard transfer-learning surgeries:

    - **position embeddings**: a checkpoint trained at e.g. 224² carries
      ``pos_embed`` for 197 tokens; fine-tuning at another resolution
      bilinearly interpolates the 2-D grid embeddings to the new token
      grid (the CLS slot passes through), exactly as the original ViT
      fine-tune recipe does;
    - **classifier head**: when ``num_classes`` differs from the
      checkpoint's head width (or the checkpoint has no head), the head is
      replaced with a zero-init one — pretrained 1000-way logits are
      meaningless for a new label set.

    Returns a new variables pytree; the input is not mutated.
    """
    params = dict(variables["params"] if "params" in variables else variables)
    patch = int(params["patch_embed"]["kernel"].shape[0])
    dim = int(params["patch_embed"]["kernel"].shape[-1])
    if image_size % patch:
        raise ValueError(
            f"image_size {image_size} is not a multiple of the checkpoint's "
            f"patch size {patch}"
        )
    tgt_grid = image_size // patch
    tgt_tokens = tgt_grid * tgt_grid + 1

    pos = jnp.asarray(params["pos_embed"])
    src_tokens = int(pos.shape[1])
    if src_tokens != tgt_tokens:
        src_grid = int(round((src_tokens - 1) ** 0.5))
        if src_grid * src_grid != src_tokens - 1:
            raise ValueError(
                f"cannot adapt pos_embed with {src_tokens} tokens: not a "
                "CLS + square grid"
            )
        cls_pos, grid_pos = pos[:, :1], pos[:, 1:]
        grid_pos = grid_pos.reshape(1, src_grid, src_grid, dim)
        grid_pos = jax.image.resize(
            grid_pos, (1, tgt_grid, tgt_grid, dim), method="bilinear"
        )
        params["pos_embed"] = jnp.concatenate(
            [cls_pos, grid_pos.reshape(1, tgt_grid * tgt_grid, dim)], axis=1
        )

    if num_classes is not None:
        head = params.get("head")
        if head is None or int(head["kernel"].shape[1]) != num_classes:
            params["head"] = {
                "kernel": jnp.zeros((dim, num_classes), jnp.float32),
                "bias": jnp.zeros((num_classes,), jnp.float32),
            }
    return {"params": params}


# ---------------------------------------------------------------------------
# google-research/vision_transformer .npz checkpoints
# ---------------------------------------------------------------------------

_GR_ATTN = "Transformer/encoderblock_{i}/MultiHeadDotProductAttention_1"
_GR_MLP = "Transformer/encoderblock_{i}/MlpBlock_3"
_GR_LN = "Transformer/encoderblock_{i}/LayerNorm_{n}"


def port_vit_npz(path: str) -> Dict[str, Any]:
    """Load a google-research/vision_transformer ``.npz`` checkpoint
    (``ViT-B_16.npz``-style naming) into the :class:`ViT` variables pytree.

    The upstream attention weights are per-head factored —
    query/key/value kernels ``(dim, heads, head_dim)``, out kernel
    ``(heads, head_dim, dim)`` — and fuse into our ``qkv``/``proj`` dense
    kernels by flattening the head axes.  Checkpoints with a ``pre_logits``
    layer (the in21k variants) are rejected: our architecture (like the
    fine-tuned upstream configs) has no pre-logits bottleneck.
    """
    z = np.load(path)
    names = set(z.files)
    if any(n.startswith("pre_logits") for n in names):
        raise ValueError(
            f"{path} has a pre_logits head (an in21k pre-training "
            "checkpoint); use a fine-tuned variant without it"
        )

    params: Dict[str, Any] = {
        "patch_embed": {
            "kernel": jnp.asarray(z["embedding/kernel"]),
            "bias": jnp.asarray(z["embedding/bias"]),
        },
        "cls_token": jnp.asarray(z["cls"]),
        "pos_embed": jnp.asarray(
            z["Transformer/posembed_input/pos_embedding"]
        ),
        "ln_final": {
            "scale": jnp.asarray(z["Transformer/encoder_norm/scale"]),
            "bias": jnp.asarray(z["Transformer/encoder_norm/bias"]),
        },
    }
    dim = int(params["patch_embed"]["kernel"].shape[-1])

    depth = 0
    while f"Transformer/encoderblock_{depth}/LayerNorm_0/scale" in names:
        depth += 1
    if depth == 0:
        raise ValueError(f"{path}: no encoderblock_* entries found")

    for i in range(depth):
        attn = _GR_ATTN.format(i=i)
        mlp = _GR_MLP.format(i=i)

        def qkv_part(which):
            k = z[f"{attn}/{which}/kernel"].reshape(dim, -1)  # (d, h*hd)
            b = z[f"{attn}/{which}/bias"].reshape(-1)
            return k, b

        (wq, bq), (wk, bk), (wv, bv) = map(qkv_part, ("query", "key", "value"))
        params[f"block_{i}"] = {
            "ln_1": {
                "scale": jnp.asarray(z[_GR_LN.format(i=i, n=0) + "/scale"]),
                "bias": jnp.asarray(z[_GR_LN.format(i=i, n=0) + "/bias"]),
            },
            "qkv": {
                "kernel": jnp.asarray(np.concatenate([wq, wk, wv], axis=1)),
                "bias": jnp.asarray(np.concatenate([bq, bk, bv])),
            },
            "proj": {
                "kernel": jnp.asarray(z[f"{attn}/out/kernel"].reshape(-1, dim)),
                "bias": jnp.asarray(z[f"{attn}/out/bias"]),
            },
            "ln_2": {
                "scale": jnp.asarray(z[_GR_LN.format(i=i, n=2) + "/scale"]),
                "bias": jnp.asarray(z[_GR_LN.format(i=i, n=2) + "/bias"]),
            },
            "mlp_up": {
                "kernel": jnp.asarray(z[f"{mlp}/Dense_0/kernel"]),
                "bias": jnp.asarray(z[f"{mlp}/Dense_0/bias"]),
            },
            "mlp_down": {
                "kernel": jnp.asarray(z[f"{mlp}/Dense_1/kernel"]),
                "bias": jnp.asarray(z[f"{mlp}/Dense_1/bias"]),
            },
        }
    if "head/kernel" in names:
        params["head"] = {
            "kernel": jnp.asarray(z["head/kernel"]),
            "bias": jnp.asarray(z["head/bias"]),
        }
    return {"params": params}


def export_vit_npz(
    variables: Dict[str, Any], path: str, heads: Optional[int] = None
) -> None:
    """Write a :class:`ViT` variables pytree as a
    google-research-vision_transformer-named ``.npz``.

    The inverse of :func:`port_vit_npz` (kernels un-fuse back into
    per-head-factored query/key/value/out).  ``heads`` defaults to the
    variant geometry inferred from the fused qkv width — pass it explicitly
    for non-registry geometries.
    """
    params = variables["params"] if "params" in variables else variables
    dim, depth = _infer_geometry(params)
    if heads is None:
        from sparkdl_tpu.models.vit import VIT_VARIANTS

        matches = [h for (_, d, dep, h, _) in VIT_VARIANTS.values()
                   if d == dim and dep == depth]
        if not matches:
            raise ValueError(
                f"cannot infer heads for dim={dim} depth={depth}; pass "
                "heads= explicitly"
            )
        heads = matches[0]
    head_dim = dim // heads

    out: Dict[str, np.ndarray] = {
        "embedding/kernel": np.asarray(params["patch_embed"]["kernel"]),
        "embedding/bias": np.asarray(params["patch_embed"]["bias"]),
        "cls": np.asarray(params["cls_token"]),
        "Transformer/posembed_input/pos_embedding": np.asarray(
            params["pos_embed"]
        ),
        "Transformer/encoder_norm/scale": np.asarray(
            params["ln_final"]["scale"]
        ),
        "Transformer/encoder_norm/bias": np.asarray(
            params["ln_final"]["bias"]
        ),
    }
    for i in range(depth):
        blk = params[f"block_{i}"]
        attn = _GR_ATTN.format(i=i)
        mlp = _GR_MLP.format(i=i)
        qkv_k = np.asarray(blk["qkv"]["kernel"])  # (dim, 3*dim)
        qkv_b = np.asarray(blk["qkv"]["bias"])
        for j, which in enumerate(("query", "key", "value")):
            out[f"{attn}/{which}/kernel"] = qkv_k[
                :, j * dim : (j + 1) * dim
            ].reshape(dim, heads, head_dim)
            out[f"{attn}/{which}/bias"] = qkv_b[
                j * dim : (j + 1) * dim
            ].reshape(heads, head_dim)
        out[f"{attn}/out/kernel"] = np.asarray(
            blk["proj"]["kernel"]
        ).reshape(heads, head_dim, dim)
        out[f"{attn}/out/bias"] = np.asarray(blk["proj"]["bias"])
        out[_GR_LN.format(i=i, n=0) + "/scale"] = np.asarray(
            blk["ln_1"]["scale"]
        )
        out[_GR_LN.format(i=i, n=0) + "/bias"] = np.asarray(
            blk["ln_1"]["bias"]
        )
        out[_GR_LN.format(i=i, n=2) + "/scale"] = np.asarray(
            blk["ln_2"]["scale"]
        )
        out[_GR_LN.format(i=i, n=2) + "/bias"] = np.asarray(
            blk["ln_2"]["bias"]
        )
        out[f"{mlp}/Dense_0/kernel"] = np.asarray(blk["mlp_up"]["kernel"])
        out[f"{mlp}/Dense_0/bias"] = np.asarray(blk["mlp_up"]["bias"])
        out[f"{mlp}/Dense_1/kernel"] = np.asarray(blk["mlp_down"]["kernel"])
        out[f"{mlp}/Dense_1/bias"] = np.asarray(blk["mlp_down"]["bias"])
    if "head" in params:
        out["head/kernel"] = np.asarray(params["head"]["kernel"])
        out["head/bias"] = np.asarray(params["head"]["bias"])
    np.savez(path, **out)
