"""MobileNetV2 (alpha=1.0) in Flax.

Parity target: ``keras.applications.mobilenet_v2.MobileNetV2`` — explicit
stable layer names (``Conv1``, ``expanded_conv_*``, ``block_N_expand`` /
``_depthwise`` / ``_project`` + ``_BN`` suffixes, ``Conv_1``).  ReLU6
activations, BN epsilon 1e-3.  Stride-2 depthwise convs use TF-SAME
asymmetric padding (equal to Keras's explicit ``correct_pad`` zero-padding
for the even feature-map sizes this net produces from square inputs).
Featurization cut point: global-average-pool output, 1280 features.
Input 224x224x3, "tf" preprocessing.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from sparkdl_tpu.models.layers import global_avg_pool

# (out_filters, stride, expansion) per inverted-residual block, block_id 0..16.
_BLOCKS = (
    (16, 1, 1),
    (24, 2, 6), (24, 1, 6),
    (32, 2, 6), (32, 1, 6), (32, 1, 6),
    (64, 2, 6), (64, 1, 6), (64, 1, 6), (64, 1, 6),
    (96, 1, 6), (96, 1, 6), (96, 1, 6),
    (160, 2, 6), (160, 1, 6), (160, 1, 6),
    (320, 1, 6),
)


def _relu6(x):
    return jnp.minimum(nn.relu(x), 6.0)


class MobileNetV2(nn.Module):
    num_classes: int = 1000
    include_top: bool = True
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False, features_only: bool = False):
        def bn(y, name):
            return nn.BatchNorm(
                use_running_average=not train,
                epsilon=1e-3,
                dtype=self.dtype,
                name=name,
            )(y)

        def depthwise(y, stride, name):
            cin = y.shape[-1]
            return nn.Conv(
                cin,
                (3, 3),
                strides=(stride, stride),
                padding="SAME",
                feature_group_count=cin,
                use_bias=False,
                dtype=self.dtype,
                name=name,
            )(y)

        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="SAME", use_bias=False,
                    dtype=self.dtype, name="Conv1")(x)
        x = _relu6(bn(x, "bn_Conv1"))

        for block_id, (filters, stride, expansion) in enumerate(_BLOCKS):
            prefix = "expanded_conv" if block_id == 0 else f"block_{block_id}"
            inputs = x
            cin = x.shape[-1]
            if expansion != 1:
                x = nn.Conv(expansion * cin, (1, 1), use_bias=False,
                            dtype=self.dtype, name=f"{prefix}_expand")(x)
                x = _relu6(bn(x, f"{prefix}_expand_BN"))
            x = depthwise(x, stride, f"{prefix}_depthwise")
            x = _relu6(bn(x, f"{prefix}_depthwise_BN"))
            x = nn.Conv(filters, (1, 1), use_bias=False,
                        dtype=self.dtype, name=f"{prefix}_project")(x)
            x = bn(x, f"{prefix}_project_BN")
            if stride == 1 and cin == filters:
                x = inputs + x

        x = nn.Conv(1280, (1, 1), use_bias=False, dtype=self.dtype,
                    name="Conv_1")(x)
        x = _relu6(bn(x, "Conv_1_bn"))

        x = global_avg_pool(x)
        if features_only or not self.include_top:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype, name="predictions")(x)
