"""TPU-native model zoo (the ``keras_applications.py``† registry analog).

The reference delegated architectures to ``keras.applications`` and only kept
a registry (name -> constructor, input size, preprocessing, featurize cut
point) in ``python/sparkdl/transformers/keras_applications.py``†.  Here the
architectures themselves are re-implemented in Flax (NHWC, bfloat16-capable,
jit/shard-friendly) with a Keras-weight importer for pretrained parity.
"""

from sparkdl_tpu.models.registry import (  # noqa: F401
    KERAS_APPLICATION_MODELS,
    SUPPORTED_MODELS,
    KerasApplicationModel,
    getKerasApplicationModel,
    get_keras_application_model,
)
from sparkdl_tpu.models.keras_port import port_keras_weights  # noqa: F401
from sparkdl_tpu.models.vit import VIT_VARIANTS, ViT  # noqa: F401
