"""Shared Flax building blocks for the model zoo.

Conventions (chosen for exact numerics parity with the Keras oracles):
- NHWC layout everywhere (TPU-native; matches Keras channels_last).
- ``'SAME'``/``'VALID'`` string padding has TensorFlow semantics in lax, so
  stride-2 SAME pads asymmetrically exactly like Keras.
- Average pooling excludes padded cells from the divisor (TF behavior).
- Layer *names* are the normalized Keras layer names produced by
  ``keras_port.normalized_layer_names`` so ported weights drop straight in.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from flax import linen as nn

Dtype = Any
PadLike = Union[str, Sequence[Tuple[int, int]]]


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


class SeparableConv(nn.Module):
    """Keras ``SeparableConv2D``: depthwise conv then 1x1 pointwise conv.

    Parameters are registered as ``depthwise_kernel`` (kh, kw, 1, cin) and
    ``pointwise_kernel`` (1, 1, cin, cout) matching the ported Keras shapes.
    """

    features: int
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: PadLike = "SAME"
    use_bias: bool = False
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x):
        cin = x.shape[-1]
        kh, kw = _pair(self.kernel_size)
        dw_kernel = self.param(
            "depthwise_kernel", nn.initializers.lecun_normal(), (kh, kw, 1, cin)
        )
        pw_kernel = self.param(
            "pointwise_kernel",
            nn.initializers.lecun_normal(),
            (1, 1, cin, self.features),
        )
        dtype = self.dtype or x.dtype
        x = jnp.asarray(x, dtype)
        x = _depthwise(
            x, jnp.asarray(dw_kernel, dtype), _pair(self.strides), self.padding
        )
        x = _pointwise(x, jnp.asarray(pw_kernel, dtype))
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,))
            x = x + jnp.asarray(bias, dtype)
        return x


def _depthwise(x, kernel, strides, padding):
    import jax.lax as lax

    cin = x.shape[-1]
    # kernel (kh, kw, 1, cin) = lax HWIO with feature_group_count=cin
    return lax.conv_general_dilated(
        x,
        kernel,
        window_strides=strides,
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=cin,
    )


def _pointwise(x, kernel):
    import jax.lax as lax

    return lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1),
        padding="VALID",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def max_pool(x, window=3, strides=2, padding="VALID"):
    return nn.max_pool(
        x, window_shape=_pair(window), strides=_pair(strides), padding=padding
    )


def avg_pool(x, window=3, strides=1, padding="SAME"):
    # TF/Keras average pooling divides by the count of *non-padded* cells.
    return nn.avg_pool(
        x,
        window_shape=_pair(window),
        strides=_pair(strides),
        padding=padding,
        count_include_pad=False,
    )


def global_avg_pool(x):
    return jnp.mean(x, axis=(1, 2))
