"""VGG16 / VGG19 in Flax.

Parity target: ``keras.applications.vgg16`` / ``vgg19`` (explicit stable layer
names ``blockN_convM``, ``fc1``, ``fc2``, ``predictions``).  The reference's
``DeepImageFeaturizer`` cut point for VGG is the ``fc2`` output (4096
features, after its inline ReLU) — ``keras_applications.py``†.  Input
224x224x3, "caffe" preprocessing (BGR, mean subtraction).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax.numpy as jnp
from flax import linen as nn

from sparkdl_tpu.models.layers import max_pool


class _VGG(nn.Module):
    blocks: Sequence[int]
    num_classes: int = 1000
    include_top: bool = True
    dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False, features_only: bool = False):
        filters = (64, 128, 256, 512, 512)
        for b, (n_convs, f) in enumerate(zip(self.blocks, filters), start=1):
            for c in range(1, n_convs + 1):
                x = nn.Conv(
                    f,
                    (3, 3),
                    padding="SAME",
                    dtype=self.dtype,
                    name=f"block{b}_conv{c}",
                )(x)
                x = nn.relu(x)
            x = max_pool(x, 2, 2)
        if not self.include_top:
            if features_only:
                # The VGG cut point IS fc2; without the top there is nothing
                # to cut at — fail loudly instead of returning a conv map.
                raise ValueError(
                    "VGG featurization (features_only=True) requires "
                    "include_top=True: the cut point is the fc2 output."
                )
            return x
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc1")(x))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc2")(x))
        if features_only:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype, name="predictions")(x)


class VGG16(_VGG):
    blocks: Sequence[int] = (2, 2, 3, 3, 3)


class VGG19(_VGG):
    blocks: Sequence[int] = (2, 2, 4, 4, 4)
