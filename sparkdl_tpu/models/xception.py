"""Xception in Flax.

Parity target: ``keras.applications.xception`` — explicit names for the
separable-conv blocks (``blockN_sepconvM``) and Keras auto-names for the four
1x1 residual projections (``conv2d``..``conv2d_3`` + matching
``batch_normalization*``), normalized per ``keras_port``.  Featurization cut
point: global-average-pool output, 2048 features.  Input 299x299x3, "tf"
preprocessing.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp
from flax import linen as nn

from sparkdl_tpu.models.layers import SeparableConv, global_avg_pool, max_pool


class Xception(nn.Module):
    """``middle_width`` widens the 728-channel middle-flow trunk (e.g. to
    768 = 6x128 for MXU lane alignment — the BASELINE.md r3 open-headroom
    experiment).  At the default 728 the module is exactly the Keras
    architecture; widened variants hold the Keras weights zero-padded
    (zero channels propagate as zeros through depthwise/pointwise/BN/relu
    and the residual adds, so numerics are unchanged)."""

    num_classes: int = 1000
    include_top: bool = True
    dtype: Optional[Any] = None
    middle_width: int = 728

    @nn.compact
    def __call__(self, x, train: bool = False, features_only: bool = False):
        def bn(y, name):
            return nn.BatchNorm(
                use_running_average=not train,
                epsilon=1e-3,
                dtype=self.dtype,
                name=name,
            )(y)

        def sep(y, filters, name):
            y = SeparableConv(filters, (3, 3), dtype=self.dtype, name=name)(y)
            return bn(y, f"{name}_bn")

        # ---- entry flow: stem ----
        x = nn.Conv(32, (3, 3), strides=(2, 2), padding="VALID", use_bias=False,
                    dtype=self.dtype, name="block1_conv1")(x)
        x = nn.relu(bn(x, "block1_conv1_bn"))
        x = nn.Conv(64, (3, 3), padding="VALID", use_bias=False,
                    dtype=self.dtype, name="block1_conv2")(x)
        x = nn.relu(bn(x, "block1_conv2_bn"))

        # ---- entry flow: 3 downsampling residual blocks ----
        width = self.middle_width
        for i, (filters, block) in enumerate(
            ((128, 2), (256, 3), (width, 4))
        ):
            res_conv = "conv2d" if i == 0 else f"conv2d_{i}"
            res_bn = ("batch_normalization" if i == 0
                      else f"batch_normalization_{i}")
            residual = nn.Conv(filters, (1, 1), strides=(2, 2), padding="SAME",
                               use_bias=False, dtype=self.dtype,
                               name=res_conv)(x)
            residual = bn(residual, res_bn)
            if block > 2:
                x = nn.relu(x)
            x = sep(x, filters, f"block{block}_sepconv1")
            x = nn.relu(x)
            x = sep(x, filters, f"block{block}_sepconv2")
            x = max_pool(x, 3, 2, "SAME")
            x = x + residual

        # ---- middle flow: 8 residual blocks of 3 sepconvs ----
        for block in range(5, 13):
            residual = x
            for j in (1, 2, 3):
                x = nn.relu(x)
                x = sep(x, width, f"block{block}_sepconv{j}")
            x = x + residual

        # ---- exit flow ----
        residual = nn.Conv(1024, (1, 1), strides=(2, 2), padding="SAME",
                           use_bias=False, dtype=self.dtype, name="conv2d_3")(x)
        residual = bn(residual, "batch_normalization_3")
        x = nn.relu(x)
        x = sep(x, width, "block13_sepconv1")
        x = nn.relu(x)
        x = sep(x, 1024, "block13_sepconv2")
        x = max_pool(x, 3, 2, "SAME")
        x = x + residual

        x = sep(x, 1536, "block14_sepconv1")
        x = nn.relu(x)
        x = sep(x, 2048, "block14_sepconv2")
        x = nn.relu(x)

        x = global_avg_pool(x)
        if features_only or not self.include_top:
            return x
        return nn.Dense(self.num_classes, dtype=self.dtype, name="predictions")(x)
