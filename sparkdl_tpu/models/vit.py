"""Vision Transformer (ViT-B/16 family) in Flax — the stretch model family.

The reference zoo is CNN-only (``keras_applications.py``† has no ViT); this
model exists for the pod-scale fine-tune stretch config (SURVEY.md §7 step
8, BASELINE.json config #5) and as the vehicle for tensor/sequence
parallelism: unlike the CNNs, a ViT has a token axis, so its attention can
run sequence-sharded (:mod:`sparkdl_tpu.parallel.context`) and its MLP/QKV
projections tensor-sharded (:mod:`sparkdl_tpu.parallel.tp`).

Architecture follows the original ViT (Dosovitskiy et al., ICLR 2021;
public reference implementation google-research/vision_transformer):
patchify conv, prepended CLS token, learned position embeddings,
pre-LayerNorm encoder blocks, final LayerNorm; ``features_only`` returns
the CLS embedding (the transfer-learning cut point, like the CNNs'
``avg_pool``).

``attn_impl`` switches the attention schedule without touching params:
``"full"`` (dense, single device) or a callable ``(q, k, v) -> out`` — e.g.
ring attention bound to a mesh axis — so the same checkpoint runs dense on
one chip and sequence-parallel on a pod.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Union

import jax.numpy as jnp
from flax import linen as nn

from sparkdl_tpu.parallel.context import full_attention

# name -> (patch, dim, depth, heads, mlp_dim)
VIT_VARIANTS = {
    "ViT-Ti/16": (16, 192, 12, 3, 768),
    "ViT-S/16": (16, 384, 12, 6, 1536),
    "ViT-B/16": (16, 768, 12, 12, 3072),
    "ViT-B/32": (32, 768, 12, 12, 3072),
    "ViT-L/16": (16, 1024, 24, 16, 4096),
}


class ViTEncoderBlock(nn.Module):
    dim: int
    heads: int
    mlp_dim: int
    dtype: Optional[Any] = None
    attn_impl: Union[str, Callable] = "full"
    # tanh-approximate gelu matches google-research/vision_transformer
    # (flax default); exact (erf) gelu matches torch/HF ViT — weight ports
    # from HF set this for bit-faithful oracle parity
    exact_gelu: bool = False

    @nn.compact
    def __call__(self, x):
        b, s, _ = x.shape
        head_dim = self.dim // self.heads

        y = nn.LayerNorm(dtype=self.dtype, name="ln_1")(x)
        qkv = nn.Dense(3 * self.dim, dtype=self.dtype, name="qkv")(y)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, self.heads, head_dim)
        k = k.reshape(b, s, self.heads, head_dim)
        v = v.reshape(b, s, self.heads, head_dim)
        if callable(self.attn_impl):
            attn = self.attn_impl(q, k, v)
        else:
            attn = full_attention(q, k, v)
        attn = attn.reshape(b, s, self.dim)
        x = x + nn.Dense(self.dim, dtype=self.dtype, name="proj")(attn)

        y = nn.LayerNorm(dtype=self.dtype, name="ln_2")(x)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype, name="mlp_up")(y)
        y = nn.gelu(y, approximate=not self.exact_gelu)
        y = nn.Dense(self.dim, dtype=self.dtype, name="mlp_down")(y)
        return x + y


class ViT(nn.Module):
    """``variant`` picks geometry; all params are explicit for tests."""

    variant: str = "ViT-B/16"
    num_classes: int = 1000
    include_top: bool = True
    dtype: Optional[Any] = None
    attn_impl: Union[str, Callable] = "full"
    image_size: int = 224
    exact_gelu: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False, features_only: bool = False):
        patch, dim, depth, heads, mlp_dim = VIT_VARIANTS[self.variant]
        b = x.shape[0]

        x = nn.Conv(
            dim,
            (patch, patch),
            strides=(patch, patch),
            padding="VALID",
            dtype=self.dtype,
            name="patch_embed",
        )(x)
        x = x.reshape(b, -1, dim)  # (b, tokens, dim)

        cls = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, dim), jnp.float32
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls.astype(x.dtype), (b, 1, dim)), x], axis=1
        )
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, x.shape[1], dim),
            jnp.float32,
        )
        x = x + pos.astype(x.dtype)

        for i in range(depth):
            x = ViTEncoderBlock(
                dim=dim,
                heads=heads,
                mlp_dim=mlp_dim,
                dtype=self.dtype,
                attn_impl=self.attn_impl,
                exact_gelu=self.exact_gelu,
                name=f"block_{i}",
            )(x)

        x = nn.LayerNorm(dtype=self.dtype, name="ln_final")(x)
        feats = x[:, 0]  # CLS token — the transfer-learning cut point
        if features_only or not self.include_top:
            return feats
        return nn.Dense(self.num_classes, dtype=self.dtype, name="head")(feats)
