"""sparkdl_tpu.data — composable async input pipelines (prefetch-to-device).

Every consumer in the engine used to hand-roll its own ingest: the
estimators' ``StreamingShardLoader`` producer thread, the transformer run
loops' inline partition load/group/resize, ``imageIO``'s silent corrupt-row
drops.  This package is the one implementation (the tf.data idea — arxiv
2101.12127 — applied to this engine): a lazy :class:`Dataset` graph of
sources (:meth:`Dataset.from_uris` / :meth:`Dataset.from_dataframe` /
:meth:`Dataset.from_arrays`) and operators —

- ``map`` — per-item transform, optionally threaded (ordered, bounded);
- ``shuffle`` — seeded, reproducing the estimators' permutation stream;
- ``shard`` — per-host strided split (GSPMD-style first-class stage);
- ``batch`` — fixed-size with the estimators' cyclic-pad policy;
- ``prefetch`` — bounded background queue, clean shutdown on close;
- ``prefetch_to_device`` — double-buffered ``device_put`` overlapping
  host→device transfer with the previous step's compute.

Instrumented with ``data.*`` metrics (rows/sec, queue depth, device-stall
histogram) via :mod:`sparkdl_tpu.utils.metrics`.
"""

from sparkdl_tpu.data.dataset import Batch, Dataset
from sparkdl_tpu.data.prefetch import PrefetchIterator
from sparkdl_tpu.data.device import default_device_placer

__all__ = [
    "Batch",
    "Dataset",
    "PrefetchIterator",
    "default_device_placer",
]
