"""Host→device placement for the pipeline's terminal stage.

``Dataset.prefetch_to_device`` needs one callable that moves a host batch
(arrays, or pytrees of arrays — the estimator ``{"x", "y", "w"}`` dicts)
onto the accelerator and returns immediately (jax dispatch is async), so
the next batch's transfer overlaps the consumer's compute on the current
one.  :func:`default_device_placer` builds that callable:

- under a live inference mesh (:func:`transformers.utils.data_parallel_mesh`
  with >1 device), batches are sharded along their leading dim with
  ``NamedSharding(mesh, P("data"))`` — the same placement the transformer
  run loops use;
- single-device (or explicitly meshless), a plain ``jax.device_put``.

Training paths that already hold a mesh pass
``place=partial(shard_batch, mesh=mesh)`` (see ``parallel.trainer``)
instead of relying on the default.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from sparkdl_tpu.data.dataset import Batch


def _tree_place(batch, put: Callable[[Any], Any]):
    """Apply ``put`` to every array leaf; ``Batch`` wrappers keep their
    ``n_real`` on the host (it drives masking math, not device compute)."""
    import jax

    if isinstance(batch, Batch):
        return Batch(_tree_place(batch.items, put), batch.n_real)
    return jax.tree_util.tree_map(put, batch)


def default_device_placer(
    mesh: Optional[Any] = None, axis: str = "data"
) -> Callable[[Any], Any]:
    """Build ``place(batch) -> batch_on_device``.

    ``mesh=None`` resolves the process inference mesh once, at build time
    (not per batch): :func:`transformers.utils.data_parallel_mesh`.  Any
    resolved mesh with more than one device shards the leading dim along
    ``axis``; otherwise plain ``device_put``.
    """
    import jax

    if mesh is None:
        from sparkdl_tpu.transformers.utils import data_parallel_mesh

        mesh = data_parallel_mesh()

    if mesh is not None and getattr(mesh, "size", 1) > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P

        n_dev = int(mesh.size)

        def put(x):
            arr = _as_array(x)
            ndim = getattr(arr, "ndim", 0)
            # leading dim must split evenly across the mesh; callers that
            # didn't mesh-round their batch (small eval sets, ragged last
            # chunks) still get on device, just unsharded
            if not ndim or arr.shape[0] % n_dev:
                return jax.device_put(arr)
            return jax.device_put(
                arr,
                NamedSharding(mesh, P(*([axis] + [None] * (ndim - 1)))),
            )

    else:

        def put(x):
            return jax.device_put(_as_array(x))

    return lambda batch: _tree_place(batch, put)


def _as_array(x):
    import numpy as np

    if isinstance(x, np.ndarray) or hasattr(x, "ndim"):
        return x
    return np.asarray(x)
