"""Bounded background prefetch — the one producer/consumer handoff.

Replaces the hand-rolled queue threads that used to live in
``estimators/data.py`` (``StreamingShardLoader``) and
``transformers/utils.py`` (``run_batched_rows``), both of which spin-polled
a 0.1 s ``put`` timeout and could drop their ``None`` sentinel when the
consumer left mid-epoch.  Here the protocol is deadlock-free by
construction:

- the producer uses plain *blocking* puts and ALWAYS pushes a final
  sentinel (its ``finally``);
- the consumer's close path sets ``cancel`` and then **drains** the queue
  until the producer thread exits — so the blocking puts always complete,
  the sentinel is never dropped, and ``close()`` returns only after the
  producer thread is joined (no leaked threads, pinned by
  ``tests/test_data_pipeline.py``).

Instrumented: ``data.queue_depth`` gauge (items ready ahead of the
consumer) and the ``data.device_stall_ms`` histogram — how long the
consumer (ultimately the device) waited on the host each ``next()``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Optional

#: end-of-stream marker (identity-compared; never leaks to consumers)
_SENTINEL = object()


class _ProducerError:
    """Wraps an upstream exception so it re-raises on the consumer side
    (and can never be confused with a legitimate item)."""

    def __init__(self, exc: BaseException):
        self.exc = exc


class PrefetchIterator:
    """Iterator over ``source`` with ``size`` items of background lookahead.

    ``source_factory`` is called once, on the producer thread, so lazy
    upstream iterators do their work off the consumer thread.  Supports the
    full iterator protocol including ``close()`` — closing mid-stream
    cancels the producer, drains the queue, and joins the thread before
    returning.
    """

    def __init__(
        self,
        source_factory: Callable[[], Iterable],
        size: int,
        on_wait_ms: Optional[Callable[[float], None]] = None,
        on_depth: Optional[Callable[[int], None]] = None,
        on_busy_s: Optional[Callable[[float], None]] = None,
        context_span=None,
    ):
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, int(size)))
        self._cancel = threading.Event()
        self._done = False
        self._on_wait_ms = on_wait_ms
        self._on_depth = on_depth
        self._on_busy_s = on_busy_s
        # trace context crosses the queue boundary EXPLICITLY: the
        # consumer captures its current span (obs.trace) and hands it
        # over here; the producer thread re-attaches it for its whole
        # run.  None (tracing off / no open span) costs nothing.
        self._context_span = context_span
        self._thread = threading.Thread(
            target=self._produce, args=(source_factory,), daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------
    def _produce(self, source_factory) -> None:
        if self._context_span is not None:
            from sparkdl_tpu.obs.trace import tracer

            with tracer.use_span(self._context_span):
                self._produce_loop(source_factory)
        else:
            self._produce_loop(source_factory)

    def _produce_loop(self, source_factory) -> None:
        it = None
        try:
            it = iter(source_factory())
            while not self._cancel.is_set():
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    return
                finally:
                    if self._on_busy_s is not None:
                        self._on_busy_s(time.perf_counter() - t0)
                # blocking put: the consumer's close path drains the queue,
                # so this always completes and the finally-sentinel below
                # is never dropped
                self._queue.put(item)
        except BaseException as exc:  # noqa: BLE001 - re-raised consumer-side
            if not self._cancel.is_set():
                self._queue.put(_ProducerError(exc))
        finally:
            # close the upstream chain promptly (generator close runs its
            # finally blocks: pools shut down, upstream prefetches join)
            close = getattr(it, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:  # pragma: no cover - best-effort cleanup
                    pass
            self._queue.put(_SENTINEL)

    # ------------------------------------------------------------------
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        t0 = time.perf_counter()
        item = self._queue.get()
        if self._on_wait_ms is not None:
            self._on_wait_ms((time.perf_counter() - t0) * 1000.0)
        if self._on_depth is not None:
            self._on_depth(self._queue.qsize())
        if item is _SENTINEL:
            self._done = True
            self._thread.join()
            raise StopIteration
        if isinstance(item, _ProducerError):
            self._done = True
            self.close()
            raise item.exc
        return item

    def close(self) -> None:
        """Cancel the producer, drain, and join — idempotent, never blocks
        forever (the producer's blocking puts complete against the drain)."""
        self._done = True
        self._cancel.set()
        while self._thread.is_alive():
            try:
                self._queue.get(timeout=0.05)
            except queue.Empty:
                pass
        self._thread.join()

    def __del__(self):  # pragma: no cover - GC safety net
        try:
            if not self._done:
                self.close()
        except Exception:
            pass
