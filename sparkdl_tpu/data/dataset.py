"""Lazy ``Dataset`` graph: sources + composable pipeline operators.

A :class:`Dataset` is a recipe, not a container — each node holds its
upstream and its parameters, and ``iter(ds)`` materializes a fresh
iterator chain.  Iterating twice re-runs the pipeline (and draws the next
permutation from a ``shuffle`` node's seeded stream, exactly like the
estimators' per-epoch ``rng.permutation`` draws).

Design rules (tf.data — arxiv 2101.12127 — adapted to this engine):

- **lazy and re-iterable**: nothing runs until iteration; epochs are
  repeated iterations of one graph;
- **deterministic**: every operator is order-preserving (``map`` with
  workers keeps submission order); ``shuffle``/``batch`` reproduce the
  estimator path's permutation stream and cyclic-pad policy bit-for-bit,
  preserving the streaming-vs-in-memory determinism contract;
- **clean shutdown**: closing a pipeline iterator mid-stream closes the
  whole chain — prefetch threads are joined, pools are released (pinned
  by ``tests/test_data_pipeline.py``).

Consumers: ``estimators/data.py`` (``StreamingShardLoader`` and both
``_fit`` loops), the transformer run loop's chunked decode
(``transformers/utils.run_batched_rows``), and anything user-side that
wants a saturated device.
"""

from __future__ import annotations

from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    NamedTuple,
    Optional,
    Sequence,
)

import numpy as np

from sparkdl_tpu.resilience import inject


class Batch(NamedTuple):
    """One fixed-size batch: ``items`` (list or stacked array, length =
    configured batch size after padding) and ``n_real`` — how many leading
    entries are real rows (the rest are cyclic padding)."""

    items: Any
    n_real: int


def _counter():
    from sparkdl_tpu.utils.metrics import metrics

    return metrics.counter("data.rows_out")


class Dataset:
    """One node of the lazy pipeline graph.  Build with the ``from_*``
    sources, chain operators, iterate to run.

    ``len(ds)`` is available when the source size is known and no operator
    changed cardinality in a data-dependent way.
    """

    def __init__(
        self,
        iter_factory: Callable[[], Iterator],
        length: Optional[int] = None,
        name: str = "dataset",
        unbounded: bool = False,
    ):
        self._iter_factory = iter_factory
        self._length = length
        self._name = name
        self._unbounded = bool(unbounded)

    @property
    def unbounded(self) -> bool:
        """True for stream-backed datasets (``from_stream``): iteration
        may never end, so whole-stream operators (``shuffle``, cyclic
        padding) are unavailable."""
        return self._unbounded

    # ------------------------------------------------------------------
    # sources
    # ------------------------------------------------------------------
    @staticmethod
    def from_items(items: Sequence, name: str = "from_items") -> "Dataset":
        """Dataset over any finite sequence (kept by reference)."""
        return Dataset(lambda: iter(items), length=len(items), name=name)

    @staticmethod
    def from_uris(uris: Sequence[str]) -> "Dataset":
        """Dataset of URI strings — the estimator ingest source (only URIs
        stay in host memory; pair with ``map(loader)`` to decode)."""
        return Dataset.from_items(list(uris), name="from_uris")

    @staticmethod
    def from_arrays(*arrays: np.ndarray) -> "Dataset":
        """Row-wise dataset over aligned arrays: one array yields its rows,
        several yield row tuples (all must share the leading dim)."""
        if not arrays:
            raise ValueError("from_arrays requires at least one array")
        arrays = tuple(np.asarray(a) for a in arrays)
        n = arrays[0].shape[0]
        for a in arrays[1:]:
            if a.shape[0] != n:
                raise ValueError(
                    "from_arrays requires aligned leading dims: "
                    f"{[a.shape[0] for a in arrays]}"
                )
        if len(arrays) == 1:
            arr = arrays[0]
            return Dataset(
                lambda: iter(arr), length=n, name="from_arrays"
            )
        return Dataset(
            lambda: zip(*arrays), length=n, name="from_arrays"
        )

    @staticmethod
    def from_files(paths: Sequence[str], retry=None) -> "Dataset":
        """Dataset of ``(path, bytes)`` pairs read lazily at iteration
        time — the source-read stage.  ``retry`` (a
        :class:`~sparkdl_tpu.resilience.policy.RetryPolicy`) re-attempts
        reads that fail transiently (``OSError`` I/O hiccups, flaky
        network filesystems); ``FileNotFoundError`` / ``PermissionError``
        are classified permanent and fail immediately."""
        paths = list(paths)

        def read_one(path: str) -> bytes:
            inject.fire("data.source")
            with open(path, "rb") as fh:
                return fh.read()

        reader = retry.wrap(read_one) if retry is not None else read_one

        def rows():
            return ((p, reader(p)) for p in paths)

        return Dataset(rows, length=len(paths), name="from_files")

    @staticmethod
    def from_dataframe(df, *cols: str) -> "Dataset":
        """Dataset over a :class:`sparkdl_tpu.sql.dataframe.DataFrame`'s
        rows.  With ``cols``, yields tuples of those columns (one column
        yields bare values); without, yields the full ``Row``s.  Collects
        once per iteration — pair with ``shard()`` so each host keeps only
        its strided split."""
        if cols:
            selected = df.select(*cols)

            def rows():
                collected = selected.collect()
                if len(cols) == 1:
                    return iter([r[cols[0]] for r in collected])
                return iter([tuple(r[c] for c in cols) for r in collected])

        else:

            def rows():
                return iter(df.collect())

        return Dataset(rows, length=df.count(), name="from_dataframe")

    @staticmethod
    def from_stream(
        source,
        poll_batch: int = 64,
        idle_wait_ms: float = 10.0,
        max_records: Optional[int] = None,
    ) -> "Dataset":
        """Unbounded dataset over a :class:`~sparkdl_tpu.streaming.
        sources.StreamSource`: each iteration polls the source and yields
        record *values* as they arrive, waiting ``idle_wait_ms`` between
        empty polls.  Iteration ends only when the source reports
        ``finished()`` (never, for a true stream) or after
        ``max_records`` (a bounded window onto the stream — handy for
        tests and snapshot jobs).

        The resulting dataset is :attr:`unbounded`: ``shuffle`` and
        cyclic padding are rejected, and ``batch`` defaults to ragged
        finals (or ``drop_remainder=True``).  For scored, exactly-once
        consumption use :class:`~sparkdl_tpu.streaming.runner.
        StreamRunner` instead — this operator is the read-only view.
        """
        import threading

        def rows():
            waiter = threading.Event()  # interruptible idle wait
            emitted = 0
            while True:
                inject.fire("streaming.poll")
                records = source.poll(poll_batch)
                if not records:
                    if source.finished():
                        return
                    waiter.wait(idle_wait_ms / 1000.0)
                    continue
                for rec in records:
                    yield rec.value
                    emitted += 1
                    if max_records is not None and emitted >= max_records:
                        return

        return Dataset(
            rows,
            length=None,
            name="from_stream",
            unbounded=max_records is None,
        )

    # ------------------------------------------------------------------
    # operators
    # ------------------------------------------------------------------
    def map(
        self,
        fn: Callable[[Any], Any],
        num_workers: int = 0,
        buffer: Optional[int] = None,
        retry=None,
    ) -> "Dataset":
        """Apply ``fn`` per item.  ``num_workers > 0`` runs ``fn`` on a
        thread pool with a bounded in-flight window (``buffer``, default
        ``2 * num_workers``) while **preserving order** — results are
        yielded in submission order, so downstream determinism contracts
        hold regardless of per-item latency.

        ``retry`` (a :class:`~sparkdl_tpu.resilience.policy.RetryPolicy`)
        re-attempts per-item transient failures with backoff; permanent
        failures (e.g. :class:`~sparkdl_tpu.image.imageIO.ImageDecodeError`
        — corrupt bytes don't heal on retry) propagate immediately.  The
        classification is ``isinstance`` against the resilience taxonomy,
        no string matching."""
        src = self

        def apply(item):
            inject.fire("data.map")
            return fn(item)

        item_fn = retry.wrap(apply) if retry is not None else apply

        if num_workers <= 0:

            def sequential():
                it = iter(src)
                try:
                    for item in it:
                        yield item_fn(item)
                finally:
                    _close_iter(it)

            return Dataset(sequential, length=self._length, name="map",
                           unbounded=self._unbounded)

        window = int(buffer) if buffer is not None else 2 * int(num_workers)
        window = max(1, window)

        def threaded():
            from collections import deque
            from concurrent.futures import ThreadPoolExecutor

            from sparkdl_tpu.obs.trace import tracer

            # explicit trace propagation: capture the current span HERE
            # (the thread driving the pipeline) and re-attach it around
            # each pool task — pool threads never inherit context
            # silently.  With tracing off, capture() is None and the
            # unwrapped item_fn runs at zero extra cost.
            span = tracer.capture()
            if span is None:
                run = item_fn
            else:
                def run(item):
                    with tracer.use_span(span):
                        return item_fn(item)

            it = iter(src)
            pending: "deque" = deque()
            pool = ThreadPoolExecutor(
                max_workers=int(num_workers),
                thread_name_prefix="sparkdl-data-map",
            )
            try:
                for item in it:
                    pending.append(pool.submit(run, item))
                    if len(pending) >= window:
                        yield pending.popleft().result()
                while pending:
                    yield pending.popleft().result()
            finally:
                for f in pending:
                    f.cancel()
                _close_iter(it)
                pool.shutdown(wait=True)

        return Dataset(threaded, length=self._length, name="map",
                       unbounded=self._unbounded)

    def shuffle(self, seed: int) -> "Dataset":
        """Seeded whole-dataset shuffle reproducing the estimators'
        permutation stream: one ``np.random.RandomState(seed % 2**32)`` is
        created per *pipeline* (first iteration), and each iteration draws
        the next ``rng.permutation(n)`` — so epoch ``e`` of this dataset
        sees exactly the estimator loop's ``e``-th epoch order.

        Materializes the upstream items per iteration (a shuffle is a
        global reorder; upstream sources here are URI/index lists, not
        decoded tensors — shuffle *before* the expensive ``map``)."""
        if self._unbounded:
            raise ValueError(
                "shuffle() is a whole-dataset reorder and cannot apply "
                "to an unbounded stream; window the stream first "
                "(from_stream(max_records=...))"
            )
        src = self
        state: Dict[str, Any] = {}

        def shuffled():
            items = list(_iterate_fully(src))
            if "rng" not in state:
                state["rng"] = np.random.RandomState(int(seed) % 2**32)
            order = state["rng"].permutation(len(items))
            return iter([items[i] for i in order])

        return Dataset(shuffled, length=self._length, name="shuffle")

    def shard(
        self,
        index: Optional[int] = None,
        count: Optional[int] = None,
    ) -> "Dataset":
        """Keep the strided split ``index::count`` — per-host sharding as a
        first-class pipeline stage (the GSPMD framing, arxiv 2105.04663)
        instead of ad-hoc index math in each caller.

        With no arguments, uses this process's position in the
        ``jax.distributed`` job via :func:`parallel.runner.host_shard_indices`
        semantics (identity when single-process)."""
        src = self

        def strided():
            if index is None or count is None:
                from sparkdl_tpu.parallel import runner

                if not runner.is_distributed():
                    return iter(_iterate_fully(src))
                import jax

                i, c = jax.process_index(), jax.process_count()
            else:
                i, c = int(index), int(count)
            if not 0 <= i < c:
                raise ValueError(f"shard index {i} outside [0, {c})")
            return (
                item
                for j, item in enumerate(_iterate_fully(src))
                if j % c == i
            )

        length = None
        if self._length is not None and index is not None and count:
            length = len(range(int(index), self._length, int(count)))
        return Dataset(strided, length=length, name="shard",
                       unbounded=self._unbounded)

    def batch(
        self,
        batch_size: int,
        pad: Optional[str] = None,
        min_batches: Optional[int] = None,
        drop_remainder: bool = False,
    ) -> "Dataset":
        """Group items into :class:`Batch` tuples of exactly ``batch_size``.

        ``pad=None`` drops nothing and emits a ragged final batch
        (``n_real < batch_size`` with ``items`` shorter).  ``pad="cyclic"``
        pads the ragged final batch by cycling from the stream's start —
        ``np.resize(all_items, k)`` — the estimator path's exact policy, so
        batch composition is bit-identical to the in-memory ``_fit`` loop.
        ``min_batches`` (with ``pad="cyclic"``) keeps emitting fully-padded
        ``n_real=0`` batches after exhaustion up to that count — the
        multi-host case where every host must run the same step count.

        ``drop_remainder=True`` discards the ragged final instead — the
        fixed-shape option for **unbounded** streams, where cyclic padding
        is impossible (it replays from a start the stream no longer holds
        and assumes an end that never comes).  On an unbounded dataset
        only ``pad=None`` semantics apply, and items are NOT retained
        after they leave their batch (a stream must run in O(batch)
        memory, not O(stream)).
        """
        if pad not in (None, "cyclic"):
            raise ValueError(f"pad must be None or 'cyclic', got {pad!r}")
        if min_batches is not None and pad != "cyclic":
            raise ValueError("min_batches requires pad='cyclic'")
        if drop_remainder and pad is not None:
            raise ValueError("drop_remainder and pad are mutually exclusive")
        if self._unbounded and pad is not None:
            raise ValueError(
                "pad='cyclic' assumes a finite source and cannot apply to "
                "an unbounded stream; use pad=None (ragged final) or "
                "drop_remainder=True"
            )
        bs = int(batch_size)
        if bs < 1:
            raise ValueError(f"batch_size must be >= 1, got {batch_size}")
        src = self
        keep_seen = pad == "cyclic"

        def batched():
            it = iter(src)
            seen: List[Any] = []
            buf: List[Any] = []
            emitted = 0
            try:
                for item in it:
                    buf.append(item)
                    if keep_seen:
                        seen.append(item)
                    if len(buf) == bs:
                        yield Batch(_pack(buf), bs)
                        emitted += 1
                        buf = []
                if buf and not drop_remainder:
                    k = len(buf)
                    if pad == "cyclic":
                        # the estimator policy: np.resize over the full
                        # stream (== np.resize(order, pad) when upstream is
                        # the epoch permutation)
                        buf = buf + _cycle_pad(seen, bs - k)
                    yield Batch(_pack(buf), k)
                    emitted += 1
                if min_batches is not None:
                    if not seen and emitted < min_batches:
                        raise ValueError(
                            "batch(min_batches=...) on an empty stream"
                        )
                    while emitted < min_batches:
                        yield Batch(_pack(_cycle_pad(seen, bs)), 0)
                        emitted += 1
            finally:
                _close_iter(it)

        length = None
        if self._length is not None:
            if drop_remainder:
                length = self._length // bs
            else:
                length = max(-(-self._length // bs), min_batches or 0)
        return Dataset(batched, length=length, name="batch",
                       unbounded=self._unbounded)

    def prefetch(self, size: int = 2) -> "Dataset":
        """Decouple producer from consumer: a background thread runs the
        upstream pipeline ``size`` items ahead through a bounded queue.
        Clean shutdown on generator close (cancel → drain → join; see
        :mod:`sparkdl_tpu.data.prefetch`).  Advances ``data.queue_depth``
        and the ``data.device_stall_ms`` wait histogram."""
        src = self

        def prefetched():
            from sparkdl_tpu.data.prefetch import PrefetchIterator
            from sparkdl_tpu.obs.trace import tracer
            from sparkdl_tpu.utils.metrics import metrics

            stall = metrics.histogram("data.device_stall_ms")
            depth = metrics.gauge("data.queue_depth")
            busy = metrics.timer("data.producer_busy")
            it = PrefetchIterator(
                lambda: iter(src),
                size,
                on_wait_ms=stall.observe,
                on_depth=depth.set,
                on_busy_s=lambda s: busy.add_seconds(s),
                # consumer-side capture: the producer thread re-attaches
                # this span, so upstream stages (and their retries) land
                # in the consumer's trace instead of an orphan context
                context_span=tracer.capture(),
            )
            try:
                for item in it:
                    yield item
            finally:
                it.close()

        return Dataset(prefetched, length=self._length, name="prefetch",
                       unbounded=self._unbounded)

    def prefetch_to_device(
        self, place: Optional[Callable[[Any], Any]] = None
    ) -> "Dataset":
        """Double-buffered device placement: dispatch batch ``i+1``'s
        host→device transfer (``place``, default
        :func:`sparkdl_tpu.data.device.default_device_placer` — mesh-aware
        like the transformer run loop) *before* yielding batch ``i``, so
        the transfer rides under the consumer's compute on ``i`` (jax
        dispatch is async).  Terminal stage: counts ``data.rows_out``."""
        src = self

        def doubled():
            from sparkdl_tpu.data.device import default_device_placer

            placer = place if place is not None else default_device_placer()
            rows_out = _counter()
            it = iter(src)
            pending = None
            try:
                for item in it:
                    placed = placer(item)  # async dispatch of i+1 ...
                    if pending is not None:
                        rows_out.add(_row_count(pending))
                        yield pending  # ... overlaps consumer compute on i
                    pending = placed
                if pending is not None:
                    rows_out.add(_row_count(pending))
                    yield pending
            finally:
                _close_iter(it)

        return Dataset(doubled, length=self._length,
                       name="prefetch_to_device", unbounded=self._unbounded)

    # ------------------------------------------------------------------
    def __iter__(self) -> Iterator:
        return self._iter_factory()

    def __len__(self) -> int:
        if self._length is None:
            raise TypeError(f"len() of unsized dataset ({self._name})")
        return self._length

    def __repr__(self) -> str:
        size = "?" if self._length is None else str(self._length)
        return f"<Dataset {self._name} n={size}>"


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _close_iter(it) -> None:
    close = getattr(it, "close", None)
    if close is not None:
        close()


def _iterate_fully(src: Iterable) -> Iterator:
    it = iter(src)
    try:
        for item in it:
            yield item
    finally:
        _close_iter(it)


def _pack(items: List[Any]):
    """Stack scalar/array items into one ndarray (what batch consumers
    index with), leave heterogeneous items as a list."""
    first = items[0]
    if isinstance(first, (int, np.integer, float, np.floating)) or (
        isinstance(first, np.ndarray)
    ):
        try:
            return np.asarray(items)
        except ValueError:  # ragged shapes: keep the list
            return list(items)
    return list(items)


def _cycle_pad(seen: List[Any], k: int) -> List[Any]:
    """``k`` pad items cycling from the stream start (``np.resize``
    semantics over arbitrary items)."""
    if k <= 0:
        return []
    if not seen:
        raise ValueError("cannot cyclically pad an empty stream")
    reps = -(-k // len(seen))
    return (seen * reps)[:k]


def _row_count(item) -> int:
    if isinstance(item, Batch):
        return int(item.n_real)
    if isinstance(item, dict):
        for v in item.values():
            return _row_count(v)
        return 1
    shape = getattr(item, "shape", None)
    if shape:
        return int(shape[0])
    return 1
